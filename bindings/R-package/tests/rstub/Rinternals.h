/* Minimal stub of the stable R C API surface used by src/mxnet_r.cc,
 * for SYNTAX-CHECK-ONLY compilation in CI (this image ships no R).
 * It validates our glue's own C++ well-formedness and catches typos in
 * our code; it does NOT substitute for compiling against real R
 * headers (R CMD INSTALL does that wherever R exists). Declarations
 * mirror R-4.x Rinternals.h for exactly the entry points we call. */
#ifndef MXR_TEST_RINTERNALS_STUB_H_
#define MXR_TEST_RINTERNALS_STUB_H_

#include <cstddef>

typedef struct SEXPREC *SEXP;
typedef long R_xlen_t;

extern SEXP R_NilValue;
extern SEXP R_DimSymbol;
extern SEXP R_NamesSymbol;

#define REALSXP 14
#define INTSXP 13
#define STRSXP 16
#define VECSXP 19
#define RAWSXP 24

extern "C" {
SEXP Rf_allocVector(unsigned int, R_xlen_t);
SEXP Rf_protect(SEXP);
void Rf_unprotect(int);
void Rf_error(const char *, ...);
int Rf_length(SEXP);
SEXP Rf_mkChar(const char *);
SEXP Rf_mkString(const char *);
SEXP Rf_ScalarLogical(int);
int Rf_asLogical(SEXP);
int Rf_asInteger(SEXP);
double Rf_asReal(SEXP);
int Rf_isNull(SEXP);
SEXP Rf_setAttrib(SEXP, SEXP, SEXP);
double *REAL(SEXP);
int *INTEGER(SEXP);
unsigned char *RAW(SEXP);
SEXP STRING_ELT(SEXP, R_xlen_t);
void SET_STRING_ELT(SEXP, R_xlen_t, SEXP);
SEXP VECTOR_ELT(SEXP, R_xlen_t);
SEXP SET_VECTOR_ELT(SEXP, R_xlen_t, SEXP);
const char *CHAR(SEXP);
SEXP R_MakeExternalPtr(void *, SEXP, SEXP);
void *R_ExternalPtrAddr(SEXP);
void R_ClearExternalPtr(SEXP);
typedef void (*R_CFinalizer_t)(SEXP);
void R_RegisterCFinalizerEx(SEXP, R_CFinalizer_t, int);

typedef void *(*DL_FUNC)();
typedef struct {
  const char *name;
  DL_FUNC fun;
  int numArgs;
} R_CallMethodDef;
typedef struct _DllInfo DllInfo;
void R_registerRoutines(DllInfo *, const void *, const R_CallMethodDef *,
                        const void *, const void *);
int R_useDynamicSymbols(DllInfo *, int);
}

#define PROTECT(x) Rf_protect(x)
#define UNPROTECT(n) Rf_unprotect(n)
#define TRUE 1
#define FALSE 0

#endif  /* MXR_TEST_RINTERNALS_STUB_H_ */
