# Data iterator wrappers over the C API iterator registry — the role of
# the reference's R-package/R/io.R (mx.io.* creators).

mx.io.create <- function(iter_name, params) {
  keys <- names(params)
  vals <- vapply(params, function(v) {
    if (is.logical(v)) ifelse(v, "True", "False") else as.character(v)
  }, "")
  structure(.Call("MXR_DataIterCreate", iter_name, as.character(keys),
                  as.character(vals), PACKAGE = "mxnet"),
            class = "mx.dataiter")
}

#' MNIST iterator (synthetic fallback when the idx files are absent,
#' like the Python frontend's MNISTIter).
mx.io.MNISTIter <- function(batch.size = 32, num.synthetic = 512,
                            seed = 1, flat = TRUE, shuffle = TRUE) {
  mx.io.create("MNISTIter", list(
    batch_size = batch.size, num_synthetic = num.synthetic,
    seed = seed, flat = flat, shuffle = shuffle))
}

#' CSV iterator (ref: src/io/iter_csv.cc role).
mx.io.CSVIter <- function(data.csv, data.shape, label.csv = NULL,
                          batch.size = 32) {
  params <- list(data_csv = data.csv,
                 data_shape = paste0("(", paste(data.shape, collapse = ","),
                                     ")"),
                 batch_size = batch.size)
  if (!is.null(label.csv)) params$label_csv <- label.csv
  mx.io.create("CSVIter", params)
}

mx.io.next <- function(it) {
  .Call("MXR_DataIterNext", unclass(it), PACKAGE = "mxnet")
}

mx.io.reset <- function(it) {
  invisible(.Call("MXR_DataIterReset", unclass(it), PACKAGE = "mxnet"))
}

mx.io.data <- function(it) {
  structure(.Call("MXR_DataIterGetData", unclass(it), PACKAGE = "mxnet"),
            class = "MXNDArray")
}

mx.io.label <- function(it) {
  structure(.Call("MXR_DataIterGetLabel", unclass(it), PACKAGE = "mxnet"),
            class = "MXNDArray")
}
