# R frontend over the .Call glue in src/mxnet_r.cc (role of the
# reference's R-package/R/*.R over its Rcpp modules).

#' Create an NDArray from an R array.
#' R memory is column-major: the SAME buffer read row-major has shape
#' rev(dim(x)), so the framework array gets reversed dims and the raw
#' buffer untouched — the reference R binding's convention. A (H, W, C,
#' N) R image batch therefore lands as an (N, C, W, H) framework array.
mx.nd.array <- function(x) {
  d <- dim(x)
  if (is.null(d)) d <- length(x)
  .Call("MXR_NDCreate", as.double(x), as.integer(rev(d)),
        PACKAGE = "mxnet")
}

#' Copy an NDArray back into an R array (dims reversed, buffer shared
#' semantics as above — inverse of mx.nd.array).
as.array.MXNDArray <- function(h) {
  flat <- .Call("MXR_NDAsArray", h, PACKAGE = "mxnet")
  array(as.vector(flat), dim = rev(dim(flat)))
}

#' Load a checkpoint (prefix-symbol.json + prefix-%04d.params).
mx.model.load <- function(prefix, epoch) {
  json <- paste(readLines(sprintf("%s-symbol.json", prefix)),
                collapse = "\n")
  params <- readBin(sprintf("%s-%04d.params", prefix, epoch), what = "raw",
                    n = file.size(sprintf("%s-%04d.params", prefix, epoch)))
  structure(list(symbol = json, params = params), class = "mx.model")
}

#' Predict. `batch` must be an R array whose REVERSED dims equal
#' `input.shape` (framework order N, C, H, W) — e.g. a (W, H, C, N)
#' image batch, the same W-and-H-swapped convention as the MATLAB
#' binding. The raw column-major buffer is passed through unchanged;
#' the result comes back with dims reversed the same way.
predict.mx.model <- function(model, batch, input.shape) {
  d <- dim(batch)
  stopifnot(identical(as.integer(rev(d)), as.integer(input.shape)))
  pred <- .Call("MXR_PredCreate", model$symbol, model$params,
                as.integer(input.shape), PACKAGE = "mxnet")
  out <- .Call("MXR_PredForward", pred, as.double(batch),
               PACKAGE = "mxnet")
  array(as.vector(out), dim = rev(dim(out)))
}

#' Round-trip a symbol's JSON through the graph loader (validation).
mx.symbol.load.json <- function(json) {
  .Call("MXR_SymbolLoadJSON", json, PACKAGE = "mxnet")
}
