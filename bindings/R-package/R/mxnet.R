# R frontend over the .Call glue in src/mxnet_r.cc (role of the
# reference's R-package/R/*.R over its Rcpp modules).

#' Create an NDArray from an R array.
#' R arrays are column-major; the framework is row-major, so dims are
#' reversed and the data transposed on the way in (and back on the way
#' out) — same convention as the reference R binding.
mx.nd.array <- function(x) {
  d <- dim(x)
  if (is.null(d)) d <- length(x)
  xt <- aperm(array(as.double(x), dim = d), rev(seq_along(d)))
  .Call("MXR_NDCreate", as.double(xt), as.integer(rev(d)),
        PACKAGE = "mxnet")
}

#' Copy an NDArray back into an R array.
as.array.MXNDArray <- function(h) {
  flat <- .Call("MXR_NDAsArray", h, PACKAGE = "mxnet")
  d <- dim(flat)
  aperm(array(flat, dim = rev(d)), rev(seq_along(d)))
}

#' Load a checkpoint (prefix-symbol.json + prefix-%04d.params).
mx.model.load <- function(prefix, epoch) {
  json <- paste(readLines(sprintf("%s-symbol.json", prefix)),
                collapse = "\n")
  params <- readBin(sprintf("%s-%04d.params", prefix, epoch), what = "raw",
                    n = file.size(sprintf("%s-%04d.params", prefix, epoch)))
  structure(list(symbol = json, params = params), class = "mx.model")
}

#' Predict: batch is an R array with dims (H, W, C, N) image-style or
#' any row-major-compatible layout; pass input.shape in framework order
#' (N, C, H, W).
predict.mx.model <- function(model, batch, input.shape) {
  pred <- .Call("MXR_PredCreate", model$symbol, model$params,
                as.integer(input.shape), PACKAGE = "mxnet")
  xt <- aperm(batch, rev(seq_along(dim(batch))))
  out <- .Call("MXR_PredForward", pred, as.double(xt), PACKAGE = "mxnet")
  aperm(array(out, dim = rev(dim(out))), rev(seq_along(dim(out))))
}

#' Round-trip a symbol's JSON through the graph loader (validation).
mx.symbol.load.json <- function(json) {
  .Call("MXR_SymbolLoadJSON", json, PACKAGE = "mxnet")
}
