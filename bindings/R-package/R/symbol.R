# Symbolic graph construction over the .Call glue — the role of the
# reference's R-package/R/symbol.R (generic creators; the per-op
# surface in R/ops.R is generated from the registry by gen_ops.py,
# like the reference generates from the C registry at install).

#' Create a placeholder variable symbol.
mx.symbol.Variable <- function(name) {
  structure(.Call("MXR_SymbolVariable", name, PACKAGE = "mxnet"),
            class = "mx.symbol")
}

#' Generic operator construction: named list arguments that are
#' mx.symbol objects become graph inputs; everything else is passed as
#' a string operator parameter (the reference's macro-generated
#' creators do exactly this split).
mx.symbol.create <- function(op, ..., name = "") {
  argv <- list(...)
  keys <- names(argv)
  if (is.null(keys)) keys <- rep("", length(argv))
  pk <- character(0); pv <- character(0)
  ik <- character(0); ih <- list()
  for (i in seq_along(argv)) {
    v <- argv[[i]]
    if (inherits(v, "mx.symbol")) {
      ik <- c(ik, keys[i])
      ih <- c(ih, list(unclass(v)))
    } else if (!is.null(v)) {
      pv <- c(pv, mx.param.string(v))
      pk <- c(pk, keys[i])
    }
  }
  structure(.Call("MXR_SymbolCreate", op, name, pk, pv, ik, ih,
                  PACKAGE = "mxnet"),
            class = "mx.symbol")
}

#' Serialise an operator parameter the way the C API expects.
mx.param.string <- function(v) {
  if (is.logical(v)) return(ifelse(v, "True", "False"))
  if (length(v) > 1) {
    return(paste0("(", paste(v, collapse = ", "), ")"))
  }
  as.character(v)
}

mx.symbol.arguments <- function(sym) {
  .Call("MXR_SymbolListArguments", unclass(sym), PACKAGE = "mxnet")
}

mx.symbol.auxiliary.states <- function(sym) {
  .Call("MXR_SymbolListAuxiliaryStates", unclass(sym), PACKAGE = "mxnet")
}

mx.symbol.tojson <- function(sym) {
  .Call("MXR_SymbolToJSON", unclass(sym), PACKAGE = "mxnet")
}

mx.symbol.fromjson <- function(json) {
  structure(.Call("MXR_SymbolFromJSON", json, PACKAGE = "mxnet"),
            class = "mx.symbol")
}

#' Shape inference. `shapes` is a named list of integer vectors in
#' framework (row-major) order. Returns list(arg=, out=, aux=) or NULL.
mx.symbol.infer.shape <- function(sym, shapes) {
  keys <- names(shapes)
  indptr <- c(0L, cumsum(vapply(shapes, length, 1L)))
  flat <- as.integer(unlist(shapes))
  .Call("MXR_SymbolInferShape", unclass(sym), keys, as.integer(indptr),
        flat, PACKAGE = "mxnet")
}

#' All registered operator names (from the live registry).
mx.symbol.list.ops <- function() {
  .Call("MXR_ListOps", PACKAGE = "mxnet")
}
