# NDArray helpers beyond the creation/readback pair in mxnet.R —
# the role of the reference's R-package/R/ndarray.R. Imperative
# mx.nd.* op functions are generated into R/ops.R.

#' Zero-filled NDArray with framework (row-major) shape.
mx.nd.zeros <- function(shape) {
  structure(.Call("MXR_NDZeros", as.integer(shape), PACKAGE = "mxnet"),
            class = "MXNDArray")
}

#' Overwrite an NDArray in place from an R array (column-major buffer
#' passed through, as in mx.nd.array).
mx.nd.set <- function(nd, x) {
  invisible(.Call("MXR_NDSet", unclass(nd), as.double(x),
                  PACKAGE = "mxnet"))
}

#' Load a .params / NDArray binary file -> named list of NDArrays.
mx.nd.load <- function(fname) {
  out <- .Call("MXR_NDLoad", fname, PACKAGE = "mxnet")
  lapply(out, function(h) structure(h, class = "MXNDArray"))
}

#' Save a named list of NDArrays.
mx.nd.save <- function(fname, arrays) {
  invisible(.Call("MXR_NDSave", fname, lapply(arrays, unclass),
                  names(arrays), PACKAGE = "mxnet"))
}

#' Invoke a registered imperative op by name.
mx.nd.invoke <- function(op, ins, params = list()) {
  keys <- names(params)
  if (is.null(keys)) keys <- character(0)
  vals <- vapply(params, mx.param.string, "")
  out <- .Call("MXR_FuncInvoke", op, lapply(ins, unclass),
               as.character(keys), as.character(vals), PACKAGE = "mxnet")
  lapply(out, function(h) structure(h, class = "MXNDArray"))
}

#' Seed the framework RNG.
mx.set.seed <- function(seed) {
  invisible(.Call("MXR_RandomSeed", as.integer(seed), PACKAGE = "mxnet"))
}
