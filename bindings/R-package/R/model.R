# FeedForward estimator in R — the reference R-package's
# mx.model.FeedForward.create (ref: R-package/R/model.R:391) over the
# .Call training surface: bind, Xavier init, per-batch
# forward/backward, engine-resident optimizer update, accuracy metric.

#' Train a FeedForward model.
#'
#' @param symbol loss-headed mx.symbol network
#' @param X training mx.dataiter (e.g. mx.io.MNISTIter)
#' @param ctx ignored (single-device cpu in the R surface)
#' @param num.round epochs
#' @param learning.rate,momentum,wd ccSGD hyperparameters
#' @param initializer "xavier" or "uniform"
#' @param verbose print per-epoch train accuracy
#' @return mx.model.ff: list(symbol json, arg.params, aux.params)
mx.model.FeedForward.create <- function(symbol, X, ctx = NULL,
                                        num.round = 5,
                                        learning.rate = 0.1,
                                        momentum = 0.9, wd = 0,
                                        initializer = "xavier",
                                        eval.metric = "accuracy",
                                        verbose = TRUE, seed = 7) {
  arg.names <- mx.symbol.arguments(symbol)
  aux.names <- mx.symbol.auxiliary.states(symbol)

  # first batch fixes the input shapes (batch-size included)
  mx.io.reset(X)
  stopifnot(mx.io.next(X))
  d0 <- as.array.MXNDArray(mx.io.data(X))
  l0 <- as.array.MXNDArray(mx.io.label(X))
  data.shape <- rev(dim(d0))
  label.shape <- rev(dim(l0))
  input.shapes <- list(data = as.integer(data.shape))
  label.name <- grep("label$", arg.names, value = TRUE)[1]
  input.shapes[[label.name]] <- as.integer(label.shape)
  inf <- mx.symbol.infer.shape(symbol, input.shapes)
  if (is.null(inf)) stop("incomplete shape inference")

  set.seed(seed)
  args <- list(); grads <- list(); reqs <- integer(length(arg.names))
  for (i in seq_along(arg.names)) {
    n <- arg.names[i]
    shp <- inf$arg[[i]]
    args[[i]] <- mx.nd.zeros(shp)
    if (n %in% names(input.shapes)) {
      grads[i] <- list(NULL)
      reqs[i] <- 0L  # null
    } else {
      mx.nd.set(args[[i]], mx.init.weight(n, shp, initializer))
      grads[[i]] <- mx.nd.zeros(shp)
      reqs[i] <- 1L  # write
    }
  }
  aux <- lapply(seq_along(aux.names), function(i) {
    a <- mx.nd.zeros(inf$aux[[i]])
    if (grepl("var$", aux.names[i])) {
      mx.nd.set(a, rep(1, prod(inf$aux[[i]])))
    }
    a
  })

  exec <- .Call("MXR_ExecutorBind", unclass(symbol), lapply(args, unclass),
                lapply(grads, function(g) if (is.null(g)) NULL else unclass(g)),
                reqs, lapply(aux, unclass), PACKAGE = "mxnet")
  opt <- .Call("MXR_OptimizerCreate", "ccsgd",
               c("momentum", "rescale_grad"),
               c(as.character(momentum),
                 as.character(1.0 / data.shape[1])), PACKAGE = "mxnet")

  param.idx <- which(reqs == 1L)
  data.idx <- match("data", arg.names)
  label.idx <- match(label.name, arg.names)

  acc <- 0
  for (round in seq_len(num.round)) {
    mx.io.reset(X)
    correct <- 0; total <- 0
    while (mx.io.next(X)) {
      db <- as.array.MXNDArray(mx.io.data(X))
      lb <- as.array.MXNDArray(mx.io.label(X))
      mx.nd.set(args[[data.idx]], db)
      mx.nd.set(args[[label.idx]], lb)
      .Call("MXR_ExecutorForward", exec, TRUE, PACKAGE = "mxnet")
      .Call("MXR_ExecutorBackward", exec, PACKAGE = "mxnet")
      for (j in seq_along(param.idx)) {
        i <- param.idx[j]
        .Call("MXR_OptimizerUpdate", opt, j - 1L, unclass(args[[i]]),
              unclass(grads[[i]]), learning.rate, wd, PACKAGE = "mxnet")
      }
      outs <- .Call("MXR_ExecutorOutputs", exec, PACKAGE = "mxnet")
      prob <- as.array.MXNDArray(structure(outs[[1]], class = "MXNDArray"))
      # prob dims (R, column-major) = rev(framework (N, C)) = (C, N)
      pred <- apply(prob, 2, which.max) - 1
      correct <- correct + sum(pred == as.vector(lb))
      total <- total + length(lb)
    }
    acc <- correct / total
    if (verbose) {
      cat(sprintf("Round [%d] Train-%s=%f\n", round, eval.metric, acc))
    }
  }

  arg.params <- list()
  for (i in param.idx) {
    arg.params[[paste0("arg:", arg.names[i])]] <- args[[i]]
  }
  aux.params <- list()
  for (i in seq_along(aux.names)) {
    aux.params[[paste0("aux:", aux.names[i])]] <- aux[[i]]
  }
  structure(list(symbol = mx.symbol.tojson(symbol),
                 arg.params = arg.params, aux.params = aux.params,
                 train.accuracy = acc),
            class = "mx.model.ff")
}

#' Name-based initialisation, the reference convention.
mx.init.weight <- function(name, shape, initializer) {
  n <- prod(shape)
  if (grepl("bias$|beta$|mean$", name)) return(rep(0, n))
  if (grepl("gamma$|var$", name)) return(rep(1, n))
  if (identical(initializer, "xavier")) {
    fan.out <- shape[1]
    fan.in <- if (length(shape) > 1) prod(shape[-1]) else shape[1]
    s <- sqrt(6 / (fan.in + fan.out))
    return(runif(n, -s, s))
  }
  runif(n, -0.07, 0.07)
}

#' Save in the shared checkpoint format (prefix-symbol.json +
#' prefix-%04d.params with arg:/aux: keys).
mx.model.save <- function(model, prefix, iteration = 1) {
  writeLines(model$symbol, sprintf("%s-symbol.json", prefix))
  all <- c(model$arg.params, model$aux.params)
  mx.nd.save(sprintf("%s-%04d.params", prefix, iteration), all)
  invisible(NULL)
}

#' Predict with a trained mx.model.ff through the predict ABI (shares
#' the path of predict.mx.model on loaded checkpoints).
predict.mx.model.ff <- function(object, batch, input.shape, ...) {
  tmp <- tempfile("rmodel")
  mx.model.save(object, tmp, 1)
  m <- mx.model.load(tmp, 1)
  predict.mx.model(m, batch, input.shape)
}
