% Cross-binding predict conformance consumer (MATLAB/Octave): the same
% shared fixture as the C++/Java/R binding tests
% (tests/fixtures/predict_conformance). Run from the repo root:
%   matlab -batch "run('bindings/matlab/test_fixture.m')"
function test_fixture()
  dir_ = 'tests/fixtures/predict_conformance';
  [in_shape, input] = read_tensor(fullfile(dir_, 'input.txt'));
  [~, want] = read_tensor(fullfile(dir_, 'expected.txt'));

  addpath('bindings/matlab');
  m = mxnet.model;
  m.load(fullfile(dir_, 'model'), 1);

  % fixture is row-major (N, F); model.forward permutes a MATLAB
  % W x H x C x N array into N C H W order, so hand it the transpose:
  % F x N column-major == N x F row-major with H=F, W=1 mapping
  batch = reshape(input, fliplr(in_shape));  % F x N column-major
  out = m.forward(batch);                    % comes back N x ... row-major

  got = out(:);
  want = want(:);
  % outputs return permuted column-major; flatten both in matched order
  got = reshape(permute(out, ndims(out):-1:1), [], 1);
  assert(numel(got) == numel(want), 'output size mismatch');
  rel = abs(got - want) ./ (abs(want) + 1e-8);
  assert(max(rel) <= 1e-3, sprintf('FAILED: max rel diff %g', max(rel)));
  fprintf('PASSED: max rel diff %.2e over %d logits\n', max(rel), numel(got));
end

function [shape, vals] = read_tensor(path)
  fid = fopen(path, 'r');
  assert(fid ~= -1, ['cannot open ', path]);
  header = fgetl(fid);
  shape = sscanf(header, '%d')';
  vals = fscanf(fid, '%f');
  fclose(fid);
end
