%% Demo: predict with a trained checkpoint from MATLAB
% Train any model with the Python frontend first, e.g.
%   model.save('lenet', 10)
% then classify a batch from MATLAB (ref: matlab/demo.m workflow).

clear model
model = mxnet.model;
model.load('lenet', 10);

% a batch of 28x28 grayscale images, W x H x C x N
img = rand(28, 28, 1, 4, 'single');

pred = model.forward(img);
[~, cls] = max(pred, [], 2);
fprintf('predicted classes: ');
fprintf('%d ', cls - 1);
fprintf('\n');

% TPU inference: model.forward(img, 'device', 'tpu', 0)
