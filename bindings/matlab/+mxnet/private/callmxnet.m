function callmxnet(func, varargin)
%CALLMXNET invoke a libc_api entry point, asserting rc == 0.
%
% Loads the TPU-native framework's C library on first use. The library
% embeds CPython, so MXNETTPU_PYTHONPATH (or the repo root two levels up
% from this file) must point at the package for the embedded interpreter.
% ref behavior: matlab/+mxnet/private/callmxnet.m in the reference wraps
% libmxnet the same way.

if ~libisloaded('libc_api')
  here = fileparts(mfilename('fullpath'));
  root = fullfile(here, '..', '..', '..', '..');  % repo root
  libdir = fullfile(root, 'mxnet_tpu', '_native');
  header = fullfile(root, 'include', 'c_predict_api.h');
  assert(exist(fullfile(libdir, 'libc_api.so'), 'file') == 2, ...
         'build the native library first (python -c "from mxnet_tpu import _native; _native.load(''c_api'')")');
  assert(exist(header, 'file') == 2, 'missing include/c_predict_api.h');
  % the embedded interpreter resolves mxnet_tpu from PYTHONPATH
  if isempty(getenv('PYTHONPATH'))
    setenv('PYTHONPATH', root);
  end
  [err, warn] = loadlibrary(fullfile(libdir, 'libc_api'), header);
  assert(isempty(err));
  if warn, warn, end %#ok<NOPRT>
end

assert(ischar(func))
ret = calllib('libc_api', func, varargin{:});
assert(ret == 0, 'mxnet call %s failed', func);
end
