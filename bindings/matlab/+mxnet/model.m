classdef model < handle
%MODEL A predict-only handle over the TPU-native framework's C predict
% API — the same surface the reference's matlab/+mxnet/model.m exposes
% over libmxnet's c_predict_api (load a -symbol.json / -NNNN.params
% checkpoint pair, run forward, fetch outputs).
%
% Device codes: 1 = cpu, 6 = tpu (include/c_api.h).
%
% Example:
%   m = mxnet.model;
%   m.load('model/lenet', 10);         % lenet-symbol.json + lenet-0010.params
%   out = m.forward(img);              % img: H x W [x C x N] single/double
%   out = m.forward(img, 'device', 'tpu', 0);

properties
  symbol   % symbol JSON text
  params   % raw bytes of the .params file
  verbose
end

properties (Access = private)
  predictor
  prev_input_size
  prev_dev_type
  prev_dev_id
end

methods
  function obj = model()
    obj.predictor = libpointer('voidPtr', 0);
    obj.prev_input_size = [];
    obj.verbose = 1;
    obj.prev_dev_type = -1;
    obj.prev_dev_id = -1;
  end

  function delete(obj)
    obj.free_predictor();
  end

  function load(obj, model_prefix, num_epoch)
  %LOAD read a checkpoint saved by save_checkpoint / FeedForward.save
  % (prefix-symbol.json + prefix-%04d.params — same format as the
  % reference, model.py save_checkpoint).
    obj.symbol = fileread([model_prefix, '-symbol.json']);
    fid = fopen(sprintf('%s-%04d.params', model_prefix, num_epoch), 'rb');
    assert(fid ~= -1, 'cannot open params file');
    obj.params = fread(fid, inf, '*uint8');
    fclose(fid);
  end

  function outputs = forward(obj, input, varargin)
  %FORWARD run the network on a batch of inputs.
  %
  % MATLAB images are W x H x C x N column-major; the framework wants
  % N x C x H x W row-major — permuting dims [2 1 3 4] and reversing
  % the shape vector gives the right memory order, exactly the
  % transform the reference's model.m documents.
    dev_type = 1;  % cpu
    dev_id = 0;
    i = 1;
    while i <= numel(varargin)
      switch lower(varargin{i})
        case 'device'
          assert(i + 2 <= numel(varargin) + 1);
          if strcmpi(varargin{i+1}, 'tpu') || strcmpi(varargin{i+1}, 'gpu')
            dev_type = 6;
          end
          dev_id = varargin{i+2};
          i = i + 3;
        otherwise
          error('unknown option %s', varargin{i});
      end
    end

    siz = size(input);
    if numel(siz) < 4
      siz = [siz, ones(1, 4 - numel(siz))];
    end
    input = permute(input, [2 1 3 4]);
    input_size = siz([4 3 1 2]);  % N C H W

    if isempty(obj.prev_input_size) || any(obj.prev_input_size ~= input_size) ...
       || dev_type ~= obj.prev_dev_type || dev_id ~= obj.prev_dev_id
      obj.free_predictor();
    end
    obj.prev_input_size = input_size;
    obj.prev_dev_type = dev_type;
    obj.prev_dev_id = dev_id;

    if obj.predictor.Value == 0
      if obj.verbose
        fprintf('create predictor with input size ');
        fprintf('%d ', input_size);
        fprintf('\n');
      end
      csize = uint32(input_size);
      callmxnet('MXPredCreate', obj.symbol, ...
                libpointer('voidPtr', obj.params), ...
                int32(numel(obj.params)), ...
                int32(dev_type), int32(dev_id), ...
                uint32(1), {'data'}, ...
                uint32([0, 4]), csize, ...
                obj.predictor);
    end

    callmxnet('MXPredSetInput', obj.predictor, 'data', ...
              single(input(:)), uint32(numel(input)));
    callmxnet('MXPredForward', obj.predictor);

    % output 0
    out_dim = libpointer('uint32Ptr', 0);
    out_shape = libpointer('uint32PtrPtr', zeros(4, 1));
    callmxnet('MXPredGetOutputShape', obj.predictor, uint32(0), ...
              out_shape, out_dim);
    setdatatype(out_shape.Value, 'uint32Ptr', out_dim.Value);
    osize = double(out_shape.Value.Value);
    n = prod(osize);
    outputs = libpointer('singlePtr', single(zeros(n, 1)));
    callmxnet('MXPredGetOutput', obj.predictor, uint32(0), ...
              outputs, uint32(n));
    % row-major -> column-major
    outputs = reshape(outputs.Value, fliplr(osize(:)'));
    outputs = permute(outputs, numel(osize):-1:1);
  end
end

methods (Access = private)
  function free_predictor(obj)
    if obj.predictor.Value ~= 0
      callmxnet('MXPredFree', obj.predictor);
      obj.predictor = libpointer('voidPtr', 0);
    end
  end
end
end
